"""Serving-subsystem corpus (docs/serving.md): concurrency correctness
(mixed q1/q3 shapes at concurrency 1/4/16 bit-identical to serial
execution), admission control (queue-full rejection, per-tenant caps),
per-tenant HBM billing (fair-share spill ordering bills the offender,
zero cross-tenant misbilling; FaultInjector works under the server),
clean shutdown with in-flight queries, the cross-query plan-rewrite
cache (parity on/off, cross-tenant hits, clone isolation, literal
sensitivity), the resizable-semaphore and jit-cache single-flight
satellites, and the session active()-stack fix."""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
import pytest

from spark_rapids_tpu import memory as MEM
from spark_rapids_tpu import retry as R
from spark_rapids_tpu import trace as TR
from spark_rapids_tpu.sql.session import TpuSparkSession

from tests.datagen import (IntegerGen, KeyStringGen, LongGen, SmallIntGen,
                           gen_batch)


@pytest.fixture(autouse=True)
def _fresh_state():
    TR.reset_tracing()
    R.reset_fault_injection()
    yield
    TR.reset_tracing()
    R.reset_fault_injection()


# ---------------------------------------------------------------------------
# Shared data + oracle results
# ---------------------------------------------------------------------------

Q1S = """
SELECT flag, status, sum(qty) AS sq, min(price) AS mn,
       max(price) AS mx, count(*) AS c
FROM lineitem WHERE qty % 5 != 0
GROUP BY flag, status ORDER BY flag, status
"""

Q3S = """
SELECT brand, sum(amt) AS sa, count(*) AS c
FROM fact JOIN dim ON item = item2
GROUP BY brand ORDER BY brand LIMIT 50
"""


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("serve_data")
    gen = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        li = gen.createDataFrame(gen_batch(
            [("flag", KeyStringGen(cardinality=3)),
             ("status", SmallIntGen()), ("qty", LongGen()),
             ("price", IntegerGen())], 3000, 31), num_partitions=4)
        li.write.mode("overwrite").parquet(str(d / "lineitem"))
        fact = gen.createDataFrame(gen_batch(
            [("k", SmallIntGen()), ("item", IntegerGen()),
             ("amt", LongGen())], 2500, 32), num_partitions=3)
        fact.write.mode("overwrite").parquet(str(d / "fact"))
        dim = gen.createDataFrame(gen_batch(
            [("item2", IntegerGen()),
             ("brand", KeyStringGen(cardinality=5))], 400, 33),
            num_partitions=2)
        dim.write.mode("overwrite").parquet(str(d / "dim"))
    finally:
        gen.stop()
    return d


def _register_views(spark, data_dir) -> None:
    spark.read.parquet(str(data_dir / "lineitem")) \
        .createOrReplaceTempView("lineitem")
    spark.read.parquet(str(data_dir / "fact")) \
        .createOrReplaceTempView("fact")
    spark.read.parquet(str(data_dir / "dim")) \
        .createOrReplaceTempView("dim")


def _serial_rows(data_dir, sql, enabled="true"):
    spark = TpuSparkSession({"spark.rapids.sql.enabled": enabled,
                             "spark.rapids.sql.batchSizeRows": "512"})
    try:
        _register_views(spark, data_dir)
        return [tuple(r) for r in
                spark.sql(sql)._execute().rows()]
    finally:
        spark.stop()


@pytest.fixture(scope="module")
def oracle(data_dir):
    """Serial single-session results (and CPU cross-check) for both
    query shapes — the bit-identity reference for every server run."""
    q1 = _serial_rows(data_dir, Q1S)
    q3 = _serial_rows(data_dir, Q3S)
    assert q1 == _serial_rows(data_dir, Q1S, enabled="false")
    assert q3 == _serial_rows(data_dir, Q3S, enabled="false")
    return {"q1": q1, "q3": q3}


def _server(data_dir, **conf):
    from spark_rapids_tpu.serve import QueryServer
    base = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512"}
    base.update({k: str(v) for k, v in conf.items()})
    srv = QueryServer(base).start()
    srv.register_view("lineitem", str(data_dir / "lineitem"))
    srv.register_view("fact", str(data_dir / "fact"))
    srv.register_view("dim", str(data_dir / "dim"))
    return srv


# ---------------------------------------------------------------------------
# Concurrency correctness: mixed q1/q3 at 1/4/16 bit-identical
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("concurrency", [1, 4, 16])
def test_mixed_workload_bit_identical(data_dir, oracle, concurrency):
    from spark_rapids_tpu.serve import ServeClient
    srv = _server(data_dir,
                  **{"spark.rapids.sql.serve.maxConcurrentQueries": 8,
                     "spark.rapids.sql.serve.maxQueued": 64,
                     "spark.rapids.sql.serve.maxConcurrentPerTenant": 8})
    errors: list = []
    results: dict = {}

    def worker(i: int) -> None:
        try:
            with ServeClient(srv.port, tenant=f"t{i % 4}") as c:
                kind = "q1" if i % 2 == 0 else "q3"
                rows = c.collect(Q1S if kind == "q1" else Q3S)
                results[i] = (kind, rows)
        except Exception as e:  # noqa: BLE001 - surfaced by the assert
            errors.append((i, repr(e)))

    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not errors, errors
        assert len(results) == concurrency
        for kind, rows in results.values():
            assert rows == oracle[kind], (
                f"{kind} under concurrency {concurrency} diverged "
                f"from serial execution")
        st = srv.stats()
        assert st["admission"]["admitted"] == concurrency
        assert st["admission"]["rejected"] == 0
    finally:
        srv.shutdown()


def test_warm_plan_cache_hit_rate(data_dir, oracle):
    """Repeated query shapes skip the rewrite: after the cold
    submission of each shape, every warm submission must hit (> 90%
    warm hit rate is the acceptance bar; this asserts 100% on the
    controlled workload) — including hits ACROSS tenants."""
    from spark_rapids_tpu.plan_cache import PLAN_CACHE
    from spark_rapids_tpu.serve import ServeClient
    srv = _server(data_dir)
    try:
        with ServeClient(srv.port, tenant="alice") as c:
            c.collect(Q1S)   # cold: populates
            c.collect(Q3S)   # cold: populates
            h0, m0 = PLAN_CACHE.hits, PLAN_CACHE.misses
            warm_headers = []
            for i in range(5):
                _, h1 = c.sql(Q1S, tenant="alice" if i % 2 else "bob")
                _, h3 = c.sql(Q3S, tenant="carol")
                warm_headers += [h1, h3]
        hits = PLAN_CACHE.hits - h0
        misses = PLAN_CACHE.misses - m0
        assert hits / max(1, hits + misses) > 0.9, (hits, misses)
        assert all(h["planCacheHit"] for h in warm_headers)
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Admission control
# ---------------------------------------------------------------------------

def _hook_slow_query(srv, slow_tenant, started, release):
    """Make the named tenant's queries block between admission and
    planning (session.sql runs AFTER the slot is acquired), so tests
    can hold an execution slot deterministically."""
    orig_session = srv._session

    def hook(tenant):
        s = orig_session(tenant)
        if tenant == slow_tenant and not getattr(s, "_slow_hook", None):
            orig_sql = s.sql

            def slow_sql(text):
                started.set()
                release.wait(timeout=60)
                return orig_sql(text)

            s._slow_hook = True
            s.sql = slow_sql
        return s

    srv._session = hook


def test_queue_full_rejection(data_dir, oracle):
    """maxConcurrentQueries=1, maxQueued=0: while one query runs, a
    second is REJECTED (status=rejected on the wire, ServeRejected in
    the client), and the counter records it."""
    from spark_rapids_tpu.serve import ServeClient
    from spark_rapids_tpu.serve.client import ServeRejected
    srv = _server(data_dir,
                  **{"spark.rapids.sql.serve.maxConcurrentQueries": 1,
                     "spark.rapids.sql.serve.maxQueued": 0})
    try:
        release = threading.Event()
        started = threading.Event()
        _hook_slow_query(srv, "slow", started, release)
        ok: dict = {}

        def long_query():
            with ServeClient(srv.port, tenant="slow") as c:
                ok["rows"] = c.collect(Q1S)

        t = threading.Thread(target=long_query)
        t.start()
        assert started.wait(timeout=60)
        # the slot is held and nothing may wait: reject
        with ServeClient(srv.port, tenant="other") as c2:
            with pytest.raises(ServeRejected):
                c2.collect(Q3S)
        release.set()
        t.join(timeout=600)
        assert ok["rows"] == oracle["q1"]
        st = srv.stats()
        assert st["admission"]["rejected"] == 1
        assert st["admission"]["tenants"]["other"]["rejected"] == 1
    finally:
        release.set()
        srv.shutdown()


def test_per_tenant_inflight_cap(data_dir):
    """One tenant cannot occupy every slot: with maxPerTenant=1 and 2
    slots, tenant A's second query WAITS (not rejected) while its
    first runs, and tenant B is admitted immediately."""
    from spark_rapids_tpu.conf import TpuConf
    from spark_rapids_tpu.serve.scheduler import AdmissionController
    ac = AdmissionController(TpuConf({
        "spark.rapids.sql.serve.maxConcurrentQueries": "2",
        "spark.rapids.sql.serve.maxConcurrentPerTenant": "1",
        "spark.rapids.sql.serve.maxQueued": "8"}))
    ac.acquire("A")
    got_b = threading.Event()
    got_a2 = threading.Event()

    def second_a():
        ac.acquire("A")
        got_a2.set()
        ac.release("A")

    t = threading.Thread(target=second_a)
    t.start()
    time.sleep(0.05)
    assert not got_a2.is_set()  # A capped at 1 in flight
    ac.acquire("B")             # B admitted despite A's queue
    got_b.set()
    ac.release("B")
    ac.release("A")             # frees A's slot -> queued A admits
    t.join(timeout=10)
    assert got_a2.is_set()
    st = ac.stats()
    assert st["admitted"] == 3 and st["rejected"] == 0


# ---------------------------------------------------------------------------
# Fair-share HBM billing (the store-level contract)
# ---------------------------------------------------------------------------

def _mk_batch(rows: int) -> "object":
    from spark_rapids_tpu.columnar.device import DeviceBatch
    from spark_rapids_tpu.columnar.host import HostBatch, HostColumn
    from spark_rapids_tpu.sql import types as T
    data = np.arange(rows, dtype=np.int64)
    hb = HostBatch(
        T.StructType([T.StructField("x", T.LongT)]),
        [HostColumn(T.LongT, data, np.ones(rows, dtype=bool))], rows)
    return DeviceBatch.from_host(hb)

def test_fair_share_spill_bills_offender_not_victim():
    """Two tenants, one hog: under pool pressure the OVER-SHARE
    tenant's batches spill first, and every spilled byte bills the
    hog's ledger — the small tenant's spillBytes stays 0 (the
    zero-cross-tenant-misbilling acceptance criterion)."""
    b_small = _mk_batch(256)
    small_bytes = b_small.sizeof()
    # budget: the small tenant + ~2 hog batches fit, the rest must spill
    store = MEM.DeviceStore(device_budget=small_bytes * 4,
                            host_budget=1 << 30,
                            spill_dir="/tmp/srt_spill_serve_test")
    try:
        with MEM.tenant_scope("small"):
            h_small = store.register(b_small, owner="SmallOp")
        hogs = []
        with MEM.tenant_scope("hog"):
            for _ in range(8):
                hogs.append(store.register(_mk_batch(256), owner="HogOp"))
        stats = store.tenant_stats()
        assert stats["hog"]["spillBytes"] > 0
        assert stats["small"]["spillBytes"] == 0, (
            "victim tenant was billed for the offender's pressure")
        # the small tenant's batch stayed resident (over-share-first
        # ordering protected it from plain-LRU eviction)
        assert h_small._state.tier == MEM.TIER_DEVICE
        # ledger reconciles: per-tenant live sums to tenanted device bytes
        live_sum = sum(s["liveBytes"] for s in stats.values())
        assert live_sum == store.device_bytes
        # fair-share signal the admission controller reads: enforcement
        # already drove the hog BELOW 1.5x its share (that is the
        # point); tighten the factor to see the residual imbalance
        store.fair_share_factor = 0.5
        assert "hog" in store.over_share_tenants()
        assert "small" not in store.over_share_tenants()
        for h in hogs:
            h.close()
        h_small.close()
    finally:
        store.close()


def test_tenant_billing_under_injected_oom_via_server(data_dir, oracle):
    """FaultInjector works under the server: queries survive injected
    OOM bit-identical, and the per-tenant ledgers record each tenant's
    activity separately."""
    from spark_rapids_tpu.serve import ServeClient
    srv = _server(data_dir,
                  **{"spark.rapids.sql.test.injectOOM": "5"})
    try:
        with ServeClient(srv.port, tenant="alice") as c:
            assert c.collect(Q1S) == oracle["q1"]
            assert c.collect(Q3S, tenant="bob") == oracle["q3"]
        stats = MEM.store_tenant_stats()
        assert stats.get("alice", {}).get("peakBytes", 0) > 0
        assert stats.get("bob", {}).get("peakBytes", 0) > 0
        # the injector actually fired under the server
        inj_conf = None
        with srv._sessions_lock:
            inj_conf = list(srv._sessions.values())[0].conf_obj
        inj = R.get_fault_injector(inj_conf)
        assert inj is not None and inj.stats().get("oomInjected", 0) > 0
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# Clean shutdown with in-flight queries
# ---------------------------------------------------------------------------

def test_clean_shutdown_with_inflight_queries(data_dir, oracle):
    from spark_rapids_tpu.serve import ServeClient
    srv = _server(data_dir)
    entered = threading.Event()
    release = threading.Event()
    _hook_slow_query(srv, "slow", entered, release)
    out: dict = {}

    def inflight():
        with ServeClient(srv.port, tenant="slow") as c:
            out["rows"] = c.collect(Q1S)

    t = threading.Thread(target=inflight)
    t.start()
    assert entered.wait(timeout=60)
    done = threading.Event()

    def do_shutdown():
        out["drained"] = srv.shutdown(timeout=120)
        done.set()

    threading.Thread(target=do_shutdown).start()
    time.sleep(0.1)
    release.set()  # let the in-flight query finish
    assert done.wait(timeout=600)
    t.join(timeout=600)
    assert out.get("drained") is True
    assert out.get("rows") == oracle["q1"], (
        "in-flight query must complete and deliver during shutdown")
    # the listener is gone: new connections fail
    with pytest.raises(OSError):
        import socket as _socket
        s = _socket.create_connection(("127.0.0.1", srv.port),
                                      timeout=2)
        # port may be rebound by the OS later; a successful connect to
        # a closed server must at least fail the round trip
        try:
            from spark_rapids_tpu.serve import protocol
            protocol.send_msg(s, {"op": "ping"})
            if protocol.recv_msg(s) is None:
                raise OSError("EOF")
        finally:
            s.close()


# ---------------------------------------------------------------------------
# Plan-rewrite cache: parity, isolation, literal sensitivity
# ---------------------------------------------------------------------------

def _pc_conf(**extra):
    conf = {"spark.rapids.sql.enabled": "true",
            "spark.rapids.sql.batchSizeRows": "512"}
    conf.update(extra)
    return conf


def test_plan_cache_parity_and_repeat_isolation(data_dir):
    off = _serial_rows(data_dir, Q1S)
    spark = TpuSparkSession(_pc_conf(
        **{"spark.rapids.sql.planCache.enabled": "true"}))
    try:
        _register_views(spark, data_dir)
        spark.start_capture()
        q = spark.sql(Q1S)
        r1 = [tuple(r) for r in q._execute().rows()]
        r2 = [tuple(r) for r in q._execute().rows()]
        r3 = [tuple(r) for r in spark.sql(Q1S)._execute().rows()]
        plans = spark.get_captured_plans()
    finally:
        spark.stop()
    assert r1 == r2 == r3 == off
    # every execution got a FRESH clone (cached template never
    # executed, no shared instances between runs)
    assert len(plans) == 3
    assert len({id(p) for p in plans}) == 3
    # per-execution metrics are independent: each clone's scan-side
    # row counts equal the others' (not doubled by shared registries)
    def out_rows(p):
        total = 0
        for node in _walk(p):
            ms = getattr(node, "metrics", None)
            if ms is not None and type(node).__name__ == \
                    "TpuColumnarToRowExec":
                total += ms.value("numOutputRows")
        return total
    counts = [out_rows(p) for p in plans]
    assert counts[0] == counts[1] == counts[2], counts


def _walk(p):
    yield p
    for op in getattr(p, "fused_ops", []):
        yield op
    for c in getattr(p, "children", []):
        yield from _walk(c)


def test_plan_cache_literal_sensitivity(data_dir):
    """Two shapes differing ONLY in a literal must not alias: a false
    hit would silently return the other query's plan."""
    spark = TpuSparkSession(_pc_conf(
        **{"spark.rapids.sql.planCache.enabled": "true"}))
    try:
        _register_views(spark, data_dir)
        a = [tuple(r) for r in spark.sql(
            "SELECT count(*) AS c FROM lineitem WHERE qty % 5 != 0"
        )._execute().rows()]
        b = [tuple(r) for r in spark.sql(
            "SELECT count(*) AS c FROM lineitem WHERE qty % 5 != 1"
        )._execute().rows()]
    finally:
        spark.stop()
    assert a != b, "literal-differing queries must plan differently"


def test_plan_cache_normalizes_expr_ids(data_dir):
    """Fresh parses allocate fresh expression ids; the signature must
    normalize them so the SAME SQL text hits."""
    from spark_rapids_tpu.plan_cache import PLAN_CACHE
    spark = TpuSparkSession(_pc_conf(
        **{"spark.rapids.sql.planCache.enabled": "true"}))
    try:
        _register_views(spark, data_dir)
        spark.sql(Q3S)._execute()
        h0 = PLAN_CACHE.hits
        spark.sql(Q3S)._execute()  # fresh parse, fresh expr ids
        assert PLAN_CACHE.hits == h0 + 1
    finally:
        spark.stop()


# ---------------------------------------------------------------------------
# Satellites: semaphore resize, jit-cache single-flight, active() stack
# ---------------------------------------------------------------------------

def test_semaphore_resizes_between_sessions():
    """Regression (satellite): the process semaphore was sized once by
    the first conf and never re-sized; later sessions with a different
    concurrentGpuTasks silently kept the stale bound."""
    import spark_rapids_tpu.resource as RES
    from spark_rapids_tpu.conf import TpuConf
    old = RES._SEMAPHORE
    try:
        RES._SEMAPHORE = None
        s1 = RES.get_semaphore(TpuConf(
            {"spark.rapids.sql.concurrentGpuTasks": "1"}))
        assert s1.permits == 1
        s2 = RES.get_semaphore(TpuConf(
            {"spark.rapids.sql.concurrentGpuTasks": "3"}))
        assert s2 is s1 and s1.permits == 3
        # grow under load: thread holds the only permit at size 1,
        # resize to 2 unblocks a second acquirer immediately
        s1.resize(1)
        s1.acquire_if_necessary()
        got = threading.Event()

        def acquirer():
            s1.acquire_if_necessary()
            got.set()
            s1.release_if_necessary()

        t = threading.Thread(target=acquirer)
        t.start()
        time.sleep(0.05)
        assert not got.is_set()
        s1.resize(2)
        t.join(timeout=10)
        assert got.is_set()
        s1.release_if_necessary()
    finally:
        RES._SEMAPHORE = old


def test_jit_cache_single_flight():
    """Concurrent get_or_build of the SAME key builds once; the loser
    blocks (recorded as contention) and reads the winner's value."""
    from spark_rapids_tpu.jit_cache import JitCache
    cache = JitCache("testSingleFlight", capacity=8)
    builds = []
    in_build = threading.Event()
    release = threading.Event()

    def build():
        builds.append(1)
        in_build.set()
        release.wait(timeout=30)
        return "compiled"

    out = {}

    def first():
        out["a"] = cache.get_or_build("k", build)

    def second():
        in_build.wait(timeout=30)
        out["b"] = cache.get_or_build("k", build)

    t1 = threading.Thread(target=first)
    t2 = threading.Thread(target=second)
    t1.start()
    t2.start()
    time.sleep(0.1)
    release.set()
    t1.join(timeout=30)
    t2.join(timeout=30)
    assert len(builds) == 1, "duplicate compile under single-flight"
    assert out["a"] == ("compiled", True)
    assert out["b"] == ("compiled", False)
    st = cache.stats()
    assert st["contention"] >= 1
    # a failing build releases its waiters and does not wedge the key
    def boom():
        raise RuntimeError("compile failed")
    with pytest.raises(RuntimeError):
        cache.get_or_build("k2", boom)
    val, was_miss = cache.get_or_build("k2", lambda: "ok")
    assert val == "ok" and was_miss


def test_active_session_stack_restored():
    """Satellite: a second live session must not clobber the first —
    stopping it restores the previous active session."""
    s1 = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    s2 = TpuSparkSession({"spark.rapids.sql.enabled": "false"})
    try:
        assert TpuSparkSession.active() is s2
        s2.stop()
        assert TpuSparkSession.active() is s1
    finally:
        s2.stop()
        s1.stop()


def test_capture_state_is_per_session():
    """Satellite: start_capture/get_captured_plans scope to THEIR
    session — a concurrent session's planning never leaks into another
    session's capture."""
    s1 = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    s2 = TpuSparkSession({"spark.rapids.sql.enabled": "true"})
    try:
        s1.start_capture()
        df2 = s2.createDataFrame({"a": [1, 2, 3]})
        df2._execute()
        df1 = s1.createDataFrame({"b": [4, 5]})
        df1._execute()
        plans1 = s1.get_captured_plans()
        assert len(plans1) == 1
        names = [f.name for f in plans1[0].schema.fields]
        assert names == ["b"], names
    finally:
        s2.stop()
        s1.stop()


# ---------------------------------------------------------------------------
# Tenant threading through observability sinks
# ---------------------------------------------------------------------------

def test_tenant_threaded_into_event_log_and_profile(data_dir, tmp_path):
    from spark_rapids_tpu.event_log import read_events
    spark = TpuSparkSession(_pc_conf(**{
        "spark.rapids.sql.serve.tenantId": "tenant-42",
        "spark.rapids.sql.eventLog.dir": str(tmp_path / "ev"),
        "spark.rapids.sql.profile.enabled": "true",
        "spark.rapids.sql.profile.dir": str(tmp_path / "prof")}))
    try:
        _register_views(spark, data_dir)
        spark.sql(Q1S)._execute()
        ppath = spark.last_profile_path
    finally:
        spark.stop()
    events = list(read_events(str(tmp_path / "ev")))
    assert events and events[-1].get("tenant") == "tenant-42"
    assert ppath is not None
    with open(ppath) as f:
        prof = json.load(f)
    assert prof.get("tenant") == "tenant-42"
    assert "tenants" in prof.get("memory", {})
    # the tenant LEDGER recorded this query's registrations
    assert prof["memory"]["tenants"].get("tenant-42", {}) \
        .get("peakBytes", 0) > 0
